// Native host-runtime kernels for pilosa_tpu.
//
// The reference accelerates its host hot loops with AMD64 assembly
// (roaring/assembly_amd64.s); the TPU build's device hot path is
// XLA/Pallas, and THIS library covers the host-side runtime loops that
// stay on CPU: protobuf varint packing for the data plane, WAL op-record
// encode/decode with FNV-1a checksums, CSV ingest parsing, and popcount
// fallbacks.  Loaded from Python via ctypes (pilosa_tpu/native.py) with a
// pure-Python fallback when the toolchain is unavailable.
//
// Build: make -C native   (produces libpilosa_native.so)

#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>
#include <unistd.h>
#include <vector>

// The ctypes bridge (pilosa_tpu/native.py) and the native-abi
// conformance rule (pilosa_tpu/analysis/abi.py) reduce every extern "C"
// signature to width classes under the LP64 model: size_t and long are
// 64-bit, int is 32-bit, pointers are 64-bit.  A target where that does
// not hold would make the hand-declared argtypes marshal into the wrong
// registers — fail the BUILD, not the first corrupted write batch.
static_assert(sizeof(size_t) == 8, "LP64 expected: size_t must be 64-bit");
static_assert(sizeof(long) == 8, "LP64 expected: long must be 64-bit");
static_assert(sizeof(int) == 4, "LP64 expected: int must be 32-bit");
static_assert(sizeof(void*) == 8, "LP64 expected: pointers must be 64-bit");

extern "C" {

// ---------------------------------------------------------------------------
// Hashes
// ---------------------------------------------------------------------------

uint64_t pn_fnv1a64(const uint8_t* data, size_t len) {
    uint64_t h = 0xCBF29CE484222325ULL;
    for (size_t i = 0; i < len; i++) {
        h ^= data[i];
        h *= 0x100000001B3ULL;
    }
    return h;
}

uint32_t pn_fnv1a32(const uint8_t* data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; i++) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h;
}

// ---------------------------------------------------------------------------
// Popcount (host fallback; device path is lax.population_count)
// ---------------------------------------------------------------------------

uint64_t pn_popcount_u32(const uint32_t* words, size_t n) {
    uint64_t total = 0;
    for (size_t i = 0; i < n; i++) total += (uint64_t)__builtin_popcount(words[i]);
    return total;
}

uint64_t pn_popcount_and_u32(const uint32_t* a, const uint32_t* b, size_t n) {
    uint64_t total = 0;
    for (size_t i = 0; i < n; i++) total += (uint64_t)__builtin_popcount(a[i] & b[i]);
    return total;
}

// ---------------------------------------------------------------------------
// Sorted-array container insert (roaring.go array containers): in-place
// binary-search + memmove over a capacity-slack buffer — the single-SetBit
// hot loop.  Returns -1 when the value is already present (no mutation),
// else the new element count.  Caller guarantees capacity > n.
// ---------------------------------------------------------------------------

int64_t pn_array_insert_u32(uint32_t* arr, int64_t n, uint32_t v) {
    int64_t lo = 0, hi = n;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (arr[mid] < v) lo = mid + 1; else hi = mid;
    }
    if (lo < n && arr[lo] == v) return -1;
    memmove(arr + lo + 1, arr + lo, (size_t)(n - lo) * sizeof(uint32_t));
    arr[lo] = v;
    return n + 1;
}

// ---------------------------------------------------------------------------
// Fused singleton-write core (fragment.go:371-459's compiled hot path):
// container binary-search + duplicate check + memmove insert + WAL record
// encode + write(2), all in ONE ctypes crossing.  The Python side keeps
// owning the numpy buffers and the container directory; this call only
// executes the common-case mutation (array container with capacity slack)
// and returns a structural-fallback code for everything else.
// ---------------------------------------------------------------------------

// Returns the new element count (>= 1) on success, with the 13-byte WAL
// record written to wal_fd (when wal_fd >= 0); -2 when the value is
// already present (no mutation, no WAL); -3 when the WAL write failed
// (the insert is NOT applied — durability-first, caller raises).
// Caller guarantees capacity > n (the Python side checks the slack).
int64_t pn_array_add_logged(uint32_t* arr, int64_t n, uint32_t v,
                            uint64_t pos, int32_t wal_fd) {
    int64_t lo = 0, hi = n;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (arr[mid] < v) lo = mid + 1; else hi = mid;
    }
    if (lo < n && arr[lo] == v) return -2;
    if (wal_fd >= 0) {
        uint8_t rec[13];
        rec[0] = 0;  // OP_ADD
        for (int j = 0; j < 8; j++) rec[1 + j] = (pos >> (8 * j)) & 0xFF;
        uint32_t chk = pn_fnv1a32(rec, 9);
        for (int j = 0; j < 4; j++) rec[9 + j] = (chk >> (8 * j)) & 0xFF;
        size_t off = 0;
        while (off < sizeof(rec)) {
            ssize_t w = write(wal_fd, rec + off, sizeof(rec) - off);
            if (w < 0) {
                if (errno == EINTR) continue;
                return -3;
            }
            off += (size_t)w;
        }
    }
    memmove(arr + lo + 1, arr + lo, (size_t)(n - lo) * sizeof(uint32_t));
    arr[lo] = v;
    return n + 1;
}

// ---------------------------------------------------------------------------
// Protobuf varint packing (wire.py data plane: packed repeated uint64)
// ---------------------------------------------------------------------------

// Encode n uint64 values as concatenated varints. Returns bytes written,
// or -1 if cap is too small. Worst case 10 bytes/value.
int64_t pn_varint_encode(const uint64_t* vals, size_t n, uint8_t* out, size_t cap) {
    size_t o = 0;
    for (size_t i = 0; i < n; i++) {
        uint64_t v = vals[i];
        do {
            if (o >= cap) return -1;
            uint8_t b = v & 0x7F;
            v >>= 7;
            out[o++] = v ? (b | 0x80) : b;
        } while (v);
    }
    return (int64_t)o;
}

// Decode concatenated varints. Returns count decoded, or -1 on truncation,
// uint64 overflow (overlong varint), or output-buffer overflow.
int64_t pn_varint_decode(const uint8_t* buf, size_t len, uint64_t* out, size_t cap) {
    size_t i = 0, n = 0;
    while (i < len) {
        uint64_t v = 0;
        int shift = 0;
        for (;;) {
            if (i >= len || shift > 63) return -1;
            uint8_t b = buf[i++];
            // Byte 10 (shift 63) may only carry the final value bit; a set
            // continuation or any higher value bit overflows uint64.
            if (shift == 63 && (b & 0xFE)) return -1;
            v |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (n >= cap) return -1;
        out[n++] = v;
    }
    return (int64_t)n;
}

// ---------------------------------------------------------------------------
// WAL op records: [typ u8 | value u64le | fnv1a32(first 9 bytes) u32le]
// (roaring.go:1560-1626 format)
// ---------------------------------------------------------------------------

void pn_oplog_encode(const uint8_t* types, const uint64_t* vals, size_t n, uint8_t* out) {
    for (size_t i = 0; i < n; i++) {
        uint8_t* rec = out + i * 13;
        rec[0] = types[i];
        uint64_t v = vals[i];
        for (int j = 0; j < 8; j++) rec[1 + j] = (v >> (8 * j)) & 0xFF;
        uint32_t chk = pn_fnv1a32(rec, 9);
        for (int j = 0; j < 4; j++) rec[9 + j] = (chk >> (8 * j)) & 0xFF;
    }
}

// Single-record encode for the SetBit hot path: one ctypes call into a
// caller-owned 13-byte buffer beats per-op Python FNV + struct packing.
void pn_oplog_encode(const uint8_t* types, const uint64_t* vals, size_t n, uint8_t* out);

void pn_op_encode1(uint8_t typ, uint64_t value, uint8_t* out) {
    pn_oplog_encode(&typ, &value, 1, out);
}

// Returns ops decoded, or -(index+1) of the first corrupt record.
int64_t pn_oplog_decode(const uint8_t* buf, size_t len, uint8_t* types, uint64_t* vals) {
    size_t n = len / 13;
    for (size_t i = 0; i < n; i++) {
        const uint8_t* rec = buf + i * 13;
        uint32_t want = 0;
        for (int j = 0; j < 4; j++) want |= (uint32_t)rec[9 + j] << (8 * j);
        if (pn_fnv1a32(rec, 9) != want) return -(int64_t)(i + 1);
        uint8_t t = rec[0];
        if (t > 1) return -(int64_t)(i + 1);
        types[i] = t;
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v |= (uint64_t)rec[1 + j] << (8 * j);
        vals[i] = v;
    }
    return (int64_t)n;
}

// ---------------------------------------------------------------------------
// CSV ingest: parse "row,col[,timestamp]\n" lines into arrays
// (ctl/import.go hot loop)
// ---------------------------------------------------------------------------

// Returns rows parsed, or -(lineno) of the first malformed line.
int64_t pn_parse_csv(const char* buf, size_t len, uint64_t* rows, uint64_t* cols,
                     int64_t* ts, size_t cap) {
    size_t i = 0, n = 0;
    int64_t line = 1;
    while (i < len) {
        // skip blank lines
        if (buf[i] == '\n' || buf[i] == '\r') {
            if (buf[i] == '\n') line++;
            i++;
            continue;
        }
        if (n >= cap) return -line;
        uint64_t vals[3] = {0, 0, 0};
        int field = 0;
        // Per-field state so "5," / ",7" / "1 2" are rejected exactly like
        // the Python fallback (int() allows surrounding, not interior,
        // whitespace; empty row/col fields are malformed).
        bool has_digit[3] = {false, false, false};
        bool digits_done[3] = {false, false, false};  // saw space after digits
        bool line_content = false;                    // any digit or comma
        for (; i < len && buf[i] != '\n'; i++) {
            char c = buf[i];
            if (c >= '0' && c <= '9') {
                if (digits_done[field]) return -line;  // "1 2" in one field
                uint64_t d = (uint64_t)(c - '0');
                // uint64 overflow check: the fallback rejects ids >= 2^64
                // rather than wrapping them onto the wrong bit.
                if (vals[field] > (0xFFFFFFFFFFFFFFFFULL - d) / 10) return -line;
                vals[field] = vals[field] * 10 + d;
                has_digit[field] = true;
                line_content = true;
            } else if (c == ',') {
                if (field >= 2) return -line;
                field++;
                line_content = true;
            } else if (c == '\r' || c == ' ') {
                if (has_digit[field]) digits_done[field] = true;
            } else {
                return -line;
            }
        }
        if (i < len) i++;  // consume newline
        if (!line_content) {  // whitespace-only line: skipped, like strip()
            line++;
            continue;
        }
        // Row and column must each carry digits; an empty (or blank)
        // timestamp field means 0 — the fallback strips the line and
        // int() strips field-surrounding spaces, so blanks are legal there.
        if (field < 1 || !has_digit[0] || !has_digit[1]) return -line;
        if (field == 2 && vals[2] > 0x7FFFFFFFFFFFFFFFULL) return -line;  // ts is int64
        rows[n] = vals[0];
        cols[n] = vals[1];
        ts[n] = (field == 2) ? (int64_t)vals[2] : 0;
        n++;
        line++;
    }
    return (int64_t)n;
}

// ---------------------------------------------------------------------------
// Gram-lane batch evaluator: answer a matched pair-count batch straight
// from the cached all-pairs AND-count Gram using the count identities
// (|a|b| = |a|+|b|-|a&b| etc.) — the executor's steady-state serving
// loop with zero per-call Python work.  Row ids map to matrix positions
// by binary search over the sorted id table.  op ids match
// pn_pql_match_pairs: 0=and 1=or 2=xor 3=andnot.
// Returns 0, or -(i+1) for the first call whose row id is not in the
// table (caller falls back to the Python path, which grows the matrix).
// ---------------------------------------------------------------------------

static inline int64_t pn_row_pos(const int64_t* rows, int64_t n, int64_t v) {
    int64_t lo = 0, hi = n;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (rows[mid] < v) lo = mid + 1; else hi = mid;
    }
    if (lo >= n || rows[lo] != v) return -1;
    return lo;
}

int64_t pn_gram_counts(const uint8_t* op_ids, const int64_t* r1, const int64_t* r2,
                       int64_t n_calls, const int64_t* rows_sorted, const int32_t* pos,
                       int64_t n_rows, const int64_t* gram, int64_t gram_dim,
                       int64_t* out) {
    for (int64_t i = 0; i < n_calls; i++) {
        int64_t i1 = pn_row_pos(rows_sorted, n_rows, r1[i]);
        int64_t i2 = pn_row_pos(rows_sorted, n_rows, r2[i]);
        if (i1 < 0 || i2 < 0) return -(i + 1);
        int64_t p1 = pos[i1], p2 = pos[i2];
        int64_t g = gram[p1 * gram_dim + p2];
        switch (op_ids[i]) {
            case 0: out[i] = g; break;                                          // and
            case 1: out[i] = gram[p1 * gram_dim + p1] + gram[p2 * gram_dim + p2] - g; break;      // or
            case 2: out[i] = gram[p1 * gram_dim + p1] + gram[p2 * gram_dim + p2] - 2 * g; break;  // xor
            case 3: out[i] = gram[p1 * gram_dim + p1] - g; break;               // andnot
            default: return -(i + 1);
        }
    }
    return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// PQL fast-path parser (pql/parser.py hot loop for batched query bodies)
//
// Parses the common grammar subset straight into a flat PREORDER call
// tree: per call (name span, n_children, n_args, arg offset); per arg
// (key span, value type, int value or string span).  Anything outside
// the subset (floats, [lists], escaped strings, >18-digit ints,
// duplicate keys, or any syntax error) returns PN_PQL_FALLBACK and the
// caller re-parses with the full Python parser, keeping semantics and
// error messages identical to the slow path.
// ---------------------------------------------------------------------------

enum {
    PN_PQL_FALLBACK = -1,
    // arg value types
    PN_V_INT = 0,
    PN_V_STRING = 1,   // quoted, no escapes; span excludes quotes
    PN_V_IDENT = 2,    // bare identifier -> string
    PN_V_TRUE = 3,
    PN_V_FALSE = 4,
    PN_V_NULL = 5,
};

namespace {

struct PqlOut {
    int32_t* cname_s;
    int32_t* cname_e;
    int32_t* cnchild;
    int32_t* cnargs;
    int32_t* cargs_off;
    int64_t call_cap;
    int32_t* ak_s;
    int32_t* ak_e;
    int32_t* atype;
    int64_t* aint;
    int32_t* av_s;
    int32_t* av_e;
    int64_t arg_cap;
};

// C++-stack recursion bound for call(): deeper nesting falls back to the
// Python parser (which raises a survivable RecursionError) instead of
// overflowing the native stack.
static const int PN_PQL_MAX_DEPTH = 100;

struct PqlParser {
    const char* s;
    int64_t len;
    int64_t i;
    PqlOut* out;
    int64_t n_calls;
    int64_t n_args;
    int depth;

    bool ws() {
        while (i < len) {
            char c = s[i];
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' || c == '\v')
                i++;
            else
                break;
        }
        return i < len;
    }
    static bool alpha(char c) { return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z'); }
    static bool identc(char c) {
        return alpha(c) || (c >= '0' && c <= '9') || c == '_' || c == '.' || c == '-';
    }
    static bool digit(char c) { return c >= '0' && c <= '9'; }

    // Returns false to trigger fallback.
    bool ident(int32_t* s_out, int32_t* e_out) {
        if (i >= len || !alpha(s[i])) return false;
        int64_t b = i++;
        while (i < len && identc(s[i])) i++;
        *s_out = (int32_t)b;
        *e_out = (int32_t)i;
        return true;
    }

    bool call() {
        if (n_calls >= out->call_cap || depth >= PN_PQL_MAX_DEPTH) return false;
        depth++;
        bool ok = call_inner();
        depth--;
        return ok;
    }

    bool call_inner() {
        int64_t me = n_calls++;
        if (!ident(&out->cname_s[me], &out->cname_e[me])) return false;
        if (!ws() || s[i] != '(') return false;
        i++;
        // children: IDENT '(' lookahead
        int32_t nchild = 0;
        for (;;) {
            if (!ws()) return false;
            int64_t save = i;
            int32_t ts_, te_;
            if (ident(&ts_, &te_) && ws() && s[i] == '(') {
                i = save;
                if (!call()) return false;
                nchild++;
                if (!ws()) return false;
                if (s[i] == ',') {
                    i++;
                    if (!ws()) return false;
                    int64_t save3 = i;
                    int32_t us_, ue_;
                    if (ident(&us_, &ue_) && ws() && s[i] == '(') {
                        i = save3;  // another child follows; comma consumed
                        continue;
                    }
                    i = save3;  // cursor after comma: args begin here
                }
                break;
            }
            i = save;
            break;
        }
        out->cnchild[me] = nchild;
        // args
        out->cargs_off[me] = (int32_t)n_args;
        int32_t nargs = 0;
        if (!ws()) return false;
        while (s[i] != ')') {
            if (n_args >= out->arg_cap) return false;
            int64_t a = n_args;
            if (!ident(&out->ak_s[a], &out->ak_e[a])) return false;
            // duplicate key check (args per call are few; O(n^2) is fine)
            for (int64_t p = out->cargs_off[me]; p < a; p++) {
                int32_t la = out->ak_e[a] - out->ak_s[a];
                int32_t lp = out->ak_e[p] - out->ak_s[p];
                if (la == lp && memcmp(s + out->ak_s[a], s + out->ak_s[p], (size_t)la) == 0)
                    return false;
            }
            if (!ws() || s[i] != '=') return false;
            i++;
            if (!value(a)) return false;
            n_args++;
            nargs++;
            if (!ws()) return false;
            if (s[i] == ',') {
                i++;
                if (!ws()) return false;
                continue;
            }
            if (s[i] != ')') return false;
        }
        i++;  // consume ')'
        out->cnargs[me] = nargs;
        return true;
    }

    bool value(int64_t a) {
        if (!ws()) return false;
        char c = s[i];
        if (c == '"' || c == '\'') {
            int64_t b = ++i;
            while (i < len && s[i] != c) {
                if (s[i] == '\\') return false;  // escapes -> fallback
                i++;
            }
            if (i >= len) return false;  // unterminated
            out->atype[a] = PN_V_STRING;
            out->av_s[a] = (int32_t)b;
            out->av_e[a] = (int32_t)i;
            i++;
            return true;
        }
        if (c == '-' || digit(c)) {
            int64_t b = i;
            if (c == '-') i++;
            int64_t dstart = i;
            while (i < len && digit(s[i])) i++;
            if (i == dstart) return false;            // bare '-'
            if (i - dstart > 18) return false;        // huge int -> fallback
            if (i < len && s[i] == '.') return false; // float -> fallback
            int64_t v = 0;
            for (int64_t p = dstart; p < i; p++) v = v * 10 + (s[p] - '0');
            if (b != dstart) v = -v;
            out->atype[a] = PN_V_INT;
            out->aint[a] = v;
            return true;
        }
        if (c == '[') return false;  // list -> fallback
        int32_t vs, ve;
        if (!ident(&vs, &ve)) return false;
        int32_t l = ve - vs;
        if (l == 4 && memcmp(s + vs, "true", 4) == 0)
            out->atype[a] = PN_V_TRUE;
        else if (l == 5 && memcmp(s + vs, "false", 5) == 0)
            out->atype[a] = PN_V_FALSE;
        else if (l == 4 && memcmp(s + vs, "null", 4) == 0)
            out->atype[a] = PN_V_NULL;
        else {
            out->atype[a] = PN_V_IDENT;
            out->av_s[a] = vs;
            out->av_e[a] = ve;
        }
        return true;
    }
};

}  // namespace

// ---------------------------------------------------------------------------
// Fused pair-count batch matcher: recognizes a request that is ENTIRELY
// Count(<op>(Bitmap(...), Bitmap(...))) calls and emits pair arrays
// directly — the executor's compiled-query lane skips tokens, ASTs, and
// per-arg Python work.  Frame names and row-key labels are interned by
// content into small tables so Python decodes each distinct string once.
// Returns the call count, or PN_PQL_FALLBACK for ANYTHING else (other
// calls, floats, escapes, duplicate/conflicting args, syntax errors) so
// the slower paths keep every behavior and error message.
// ---------------------------------------------------------------------------

namespace {

struct PairMatcher {
    const char* s;
    int64_t len;
    int64_t i;

    bool ws() {
        while (i < len) {
            char c = s[i];
            if (c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == '\f' || c == '\v')
                i++;
            else
                break;
        }
        return i < len;
    }
    bool lit(const char* word, int n) {
        if (i + n > len || memcmp(s + i, word, (size_t)n) != 0) return false;
        // Must not extend into a longer identifier.
        if (i + n < len && PqlParser::identc(s[i + n])) return false;
        i += n;
        return true;
    }
    bool ch(char c) {
        if (i >= len || s[i] != c) return false;
        i++;
        return true;
    }
    bool ident(int32_t* b, int32_t* e) {
        if (i >= len || !PqlParser::alpha(s[i])) return false;
        int64_t st = i++;
        while (i < len && PqlParser::identc(s[i])) i++;
        *b = (int32_t)st;
        *e = (int32_t)i;
        return true;
    }
    bool integer(int64_t* out) {
        if (i >= len || s[i] < '0' || s[i] > '9') return false;
        int64_t st = i;
        int64_t v = 0;
        while (i < len && s[i] >= '0' && s[i] <= '9') {
            if (i - st >= 18) return false;  // bound BEFORE accumulating: no overflow UB
            v = v * 10 + (s[i++] - '0');
        }
        if (i < len && (s[i] == '.' || PqlParser::identc(s[i]))) return false;
        *out = v;
        return true;
    }
};

// Intern a span by content into (tab_s, tab_e, n_tab); returns index.
static int32_t intern_span(const char* s, int32_t b, int32_t e, int32_t* tab_s,
                           int32_t* tab_e, int32_t* n_tab, int32_t cap) {
    for (int32_t t = 0; t < *n_tab; t++) {
        int32_t l = tab_e[t] - tab_s[t];
        if (l == e - b && memcmp(s + tab_s[t], s + b, (size_t)l) == 0) return t;
    }
    if (*n_tab >= cap) return -2;
    tab_s[*n_tab] = b;
    tab_e[*n_tab] = e;
    return (*n_tab)++;
}

}  // namespace

extern "C" {

// op ids: 0=and(Intersect) 1=or(Union) 2=xor(Xor) 3=andnot(Difference)
// frame_id -1 = default frame.  Returns matched call count, or
// PN_PQL_FALLBACK.  Tables: unique frame spans and row-key spans.
int64_t pn_pql_match_pairs(const char* src, int64_t len,
                           uint8_t* op_ids, int32_t* frame_ids, int32_t* key_ids,
                           int64_t* r1, int64_t* r2, int64_t call_cap,
                           int32_t* uf_s, int32_t* uf_e, int32_t* n_frames,
                           int32_t* uk_s, int32_t* uk_e, int32_t* n_keys,
                           int32_t tab_cap) {
    PairMatcher p = {src, len, 0};
    int64_t n = 0;
    *n_frames = 0;
    *n_keys = 0;
    while (p.ws()) {
        if (n >= call_cap) return PN_PQL_FALLBACK;
        if (!p.lit("Count", 5)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('(')) return PN_PQL_FALLBACK;
        if (!p.ws()) return PN_PQL_FALLBACK;
        uint8_t op;
        int n_leaves = 2;
        if (p.lit("Intersect", 9)) op = 0;
        else if (p.lit("Union", 5)) op = 1;
        else if (p.lit("Xor", 3)) op = 2;
        else if (p.lit("Difference", 10)) op = 3;
        else if (p.i + 6 <= p.len && memcmp(src + p.i, "Bitmap", 6) == 0) {
            // Plain row count Count(Bitmap(...)): |r| == |r & r| — emit
            // the pair (r, r) with op AND so it rides the same lanes.
            op = 0;
            n_leaves = 1;
        } else return PN_PQL_FALLBACK;
        if (n_leaves == 2 && (!p.ws() || !p.ch('('))) return PN_PQL_FALLBACK;
        int32_t fid[2], kid[2];
        int64_t row[2];
        for (int leaf = 0; leaf < n_leaves; leaf++) {
            if (!p.ws() || !p.lit("Bitmap", 6)) return PN_PQL_FALLBACK;
            if (!p.ws() || !p.ch('(')) return PN_PQL_FALLBACK;
            int32_t f_s = -1, f_e = -1, k_s = -1, k_e = -1;
            int64_t rv = -1;
            for (int a = 0; a < 2; a++) {
                if (!p.ws()) return PN_PQL_FALLBACK;
                int32_t ks, ke;
                if (!p.ident(&ks, &ke)) return PN_PQL_FALLBACK;
                if (!p.ws() || !p.ch('=')) return PN_PQL_FALLBACK;
                if (!p.ws()) return PN_PQL_FALLBACK;
                if (ke - ks == 5 && memcmp(src + ks, "frame", 5) == 0) {
                    if (f_s >= 0) return PN_PQL_FALLBACK;  // duplicate frame=
                    char q = src[p.i];
                    if (q == '"' || q == '\'') {
                        p.i++;
                        f_s = (int32_t)p.i;
                        while (p.i < len && src[p.i] != q) {
                            if (src[p.i] == '\\') return PN_PQL_FALLBACK;
                            p.i++;
                        }
                        if (p.i >= len) return PN_PQL_FALLBACK;
                        f_e = (int32_t)p.i;
                        p.i++;
                    } else if (!p.ident(&f_s, &f_e)) {
                        return PN_PQL_FALLBACK;
                    }
                } else {
                    if (rv >= 0) return PN_PQL_FALLBACK;  // two int keys
                    if (!p.integer(&rv)) return PN_PQL_FALLBACK;
                    k_s = ks;
                    k_e = ke;
                }
                if (!p.ws()) return PN_PQL_FALLBACK;
                if (src[p.i] == ',') {
                    p.i++;
                    continue;
                }
                break;
            }
            if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;
            if (rv < 0 || k_s < 0) return PN_PQL_FALLBACK;
            fid[leaf] = (f_s < 0)
                            ? -1
                            : intern_span(src, f_s, f_e, uf_s, uf_e, n_frames, tab_cap);
            kid[leaf] = intern_span(src, k_s, k_e, uk_s, uk_e, n_keys, tab_cap);
            if (fid[leaf] == -2 || kid[leaf] == -2) return PN_PQL_FALLBACK;
            row[leaf] = rv;
            if (leaf == 0 && n_leaves == 2) {
                if (!p.ws() || !p.ch(',')) return PN_PQL_FALLBACK;
            }
        }
        if (n_leaves == 1) {  // Count(Bitmap(...)): the leaf IS the op body
            fid[1] = fid[0];
            kid[1] = kid[0];
            row[1] = row[0];
        } else {
            if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;  // close op
        }
        if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;  // close Count
        if (fid[0] != fid[1] || kid[0] != kid[1]) return PN_PQL_FALLBACK;
        op_ids[n] = op;
        frame_ids[n] = fid[0];
        key_ids[n] = kid[0];
        r1[n] = row[0];
        r2[n] = row[1];
        n++;
    }
    return n >= 2 ? n : PN_PQL_FALLBACK;
}

}  // extern "C"

extern "C" {

// ---------------------------------------------------------------------------
// One-call serving lane (server.go:150 + executor.go:1209-1244 analog):
// parse + validate + Gram-evaluate an entire batched pair-count request
// in a single GIL-released crossing.  The Python side caches the serve
// state (expected frame/row-label bytes, the sorted row-id table and
// Gram snapshot) and revalidates it per request with generation checks;
// THIS call does everything else.  Returns the call count with counts
// in out[], or PN_PQL_FALLBACK for anything outside the exact shape
// (other frames, wrong row-key label, unknown rows, parse mismatch) —
// the caller then runs the general path, which also refreshes the
// cached state.
// ---------------------------------------------------------------------------

int64_t pn_serve_pairs(const char* src, int64_t len,
                       const char* frame, int64_t flen, int64_t allow_default,
                       const char* rowkey, int64_t klen,
                       const int64_t* rows_sorted, const int32_t* pos,
                       int64_t n_rows, const int64_t* gram, int64_t gram_dim,
                       int64_t* out, int64_t cap) {
    enum { MAXC = 4096, TAB = 16 };
    static thread_local uint8_t op_ids[MAXC];
    static thread_local int32_t frame_ids[MAXC], key_ids[MAXC];
    static thread_local int64_t r1[MAXC], r2[MAXC];
    int32_t uf_s[TAB], uf_e[TAB], uk_s[TAB], uk_e[TAB];
    int32_t n_frames = 0, n_keys = 0;
    int64_t n = pn_pql_match_pairs(src, len, op_ids, frame_ids, key_ids, r1, r2,
                                   cap < MAXC ? cap : MAXC,
                                   uf_s, uf_e, &n_frames, uk_s, uk_e, &n_keys,
                                   TAB);
    if (n < 0) return PN_PQL_FALLBACK;
    // Every frame reference must be the cached frame (an absent frame=
    // arg is the default frame, allowed only when the cached frame IS
    // the default); every row-key label must be the frame's row label.
    for (int32_t t = 0; t < n_frames; t++) {
        int32_t l = uf_e[t] - uf_s[t];
        if (l != flen || memcmp(src + uf_s[t], frame, (size_t)l) != 0)
            return PN_PQL_FALLBACK;
    }
    for (int32_t t = 0; t < n_keys; t++) {
        int32_t l = uk_e[t] - uk_s[t];
        if (l != klen || memcmp(src + uk_s[t], rowkey, (size_t)l) != 0)
            return PN_PQL_FALLBACK;
    }
    if (!allow_default) {
        for (int64_t i = 0; i < n; i++)
            if (frame_ids[i] < 0) return PN_PQL_FALLBACK;
    }
    if (pn_gram_counts(op_ids, r1, r2, n, rows_sorted, pos, n_rows, gram,
                       gram_dim, out) != 0)
        return PN_PQL_FALLBACK;
    return n;
}

// ---------------------------------------------------------------------------
// Native write request lane (the write-side twin of pn_serve_pairs):
// parse a canonical all-SetBit/ClearBit request body, validate every op
// against the caller's per-container table (sorted keys -> capacity-
// slack array buffers), apply the sorted inserts/removes SEQUENTIALLY
// (in-batch duplicate and set-then-clear semantics identical to issuing
// the calls one by one), and append ONE group-committed WAL write(2) of
// the 13-byte op records — all in a single GIL-released crossing.
//
// Parse shape per call (the canonical client/bench shape, the batched
// generalization of executor.py's _SINGLETON_WRITE_RX):
//
//   SetBit(<rowkey>=INT, frame="<frame>", <colkey>=INT)
//   ClearBit(<rowkey>=INT, frame='<frame>', <colkey>=INT)
//
// frame may be quoted or a bare identifier but must equal the armed
// frame; rowkey/colkey must equal the armed labels.  ANY deviation
// (other calls, timestamps, reordered args, other frames) returns
// PN_PQL_FALLBACK with nothing parsed and nothing mutated — the Python
// general lane keeps every behavior and error message.
//
// Outcomes:
//   ret >= 1, *applied = 1   ops applied + WAL written; changed[] valid.
//   ret >= 1, *applied = 0   parsed only (structural decline: container
//                            missing/bitmap/no slack, op outside the
//                            armed slice, would empty on clear, huge
//                            batch).  types/rows/cols arrays are valid;
//                            NOTHING was mutated — the caller applies
//                            through the Python batch path using the
//                            parse (still skipping the Python tokenizer).
//   PN_PQL_FALLBACK          parse mismatch; nothing touched.
//   -3                       WAL write failed AFTER mutation (matching
//                            the Python batch lane's apply-then-log
//                            order); caller raises.
// ---------------------------------------------------------------------------

namespace {

// Binary search over the sorted container-key table; -1 when absent.
static inline int64_t pn_tab_pos(const uint64_t* keys, int64_t n, uint64_t v) {
    int64_t lo = 0, hi = n;
    while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (keys[mid] < v) lo = mid + 1; else hi = mid;
    }
    if (lo >= n || keys[lo] != v) return -1;
    return lo;
}

}  // namespace

int64_t pn_write_batch(const char* src, int64_t len,
                       const char* frame, int64_t flen,
                       const char* rowkey, int64_t klen,
                       const char* colkey, int64_t clen,
                       uint64_t slice_i, uint64_t slice_width,
                       const uint64_t* keys_sorted, uint64_t* buf_addrs,
                       int64_t* ns, const int64_t* caps, int64_t n_containers,
                       int64_t array_max, int32_t wal_fd,
                       uint8_t* types_out, uint64_t* rows_out, uint64_t* cols_out,
                       uint8_t* changed_out, int64_t cap, int64_t* applied) {
    *applied = 0;
    if (slice_width == 0) return PN_PQL_FALLBACK;
    PairMatcher p = {src, len, 0};
    int64_t n = 0;
    while (p.ws()) {
        if (n >= cap) return PN_PQL_FALLBACK;
        uint8_t typ;
        if (p.lit("SetBit", 6)) typ = 0;
        else if (p.lit("ClearBit", 8)) typ = 1;
        else return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('(')) return PN_PQL_FALLBACK;
        // arg 1: <rowkey>=INT
        int32_t ks, ke;
        int64_t row = -1, col = -1;
        if (!p.ws() || !p.ident(&ks, &ke)) return PN_PQL_FALLBACK;
        if (ke - ks != klen || memcmp(src + ks, rowkey, (size_t)klen) != 0)
            return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('=')) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.integer(&row)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch(',')) return PN_PQL_FALLBACK;
        // arg 2: frame="<frame>" (quoted or bare, content must match)
        if (!p.ws() || !p.lit("frame", 5)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('=')) return PN_PQL_FALLBACK;
        if (!p.ws()) return PN_PQL_FALLBACK;
        {
            int32_t fs, fe;
            char q = src[p.i];
            if (q == '"' || q == '\'') {
                p.i++;
                fs = (int32_t)p.i;
                while (p.i < len && src[p.i] != q) {
                    if (src[p.i] == '\\') return PN_PQL_FALLBACK;
                    p.i++;
                }
                if (p.i >= len) return PN_PQL_FALLBACK;
                fe = (int32_t)p.i;
                p.i++;
            } else if (!p.ident(&fs, &fe)) {
                return PN_PQL_FALLBACK;
            }
            if (fe - fs != flen || memcmp(src + fs, frame, (size_t)flen) != 0)
                return PN_PQL_FALLBACK;
        }
        if (!p.ws() || !p.ch(',')) return PN_PQL_FALLBACK;
        // arg 3: <colkey>=INT
        if (!p.ws() || !p.ident(&ks, &ke)) return PN_PQL_FALLBACK;
        if (ke - ks != clen || memcmp(src + ks, colkey, (size_t)clen) != 0)
            return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('=')) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.integer(&col)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;
        // pos = row*W + col%W, overflow-guarded (integer() bounds each
        // value to < 10^18, but the product can still exceed uint64).
        uint64_t r = (uint64_t)row, c = (uint64_t)col;
        if (r > (0xFFFFFFFFFFFFFFFFULL - c % slice_width) / slice_width)
            return PN_PQL_FALLBACK;
        if (c / slice_width != slice_i) {
            // Outside the armed fragment's slice: keep parsing (the
            // parse is still reusable) but never apply natively.
            n_containers = -1;
        }
        types_out[n] = typ;
        rows_out[n] = r;
        cols_out[n] = c;
        n++;
    }
    if (n < 1) return PN_PQL_FALLBACK;
    if (n_containers < 0) return n;  // cross-slice batch: parsed only
    // Huge batches: pass 1's O(n^2) per-container op counting stops
    // paying; hand the parse to the vectorized Python batch path.
    if (n > 1024) return n;

    // Pass 1 — conservative structural validation with NO mutation:
    // every op's container must be an array with enough slack for every
    // op that might land in it (adds), and enough occupancy that clears
    // can never empty it.  Anything else: parsed-only.
    for (int64_t i = 0; i < n; i++) {
        uint64_t pos_i = rows_out[i] * slice_width + cols_out[i] % slice_width;
        int64_t t = pn_tab_pos(keys_sorted, n_containers, pos_i >> 16);
        if (t < 0) return n;  // absent or non-array container
        // Count ops targeting this container (n is small; O(n^2) over a
        // request batch beats allocating a side table).  Sets and
        // clears bound different hazards: sets the capacity/conversion
        // ceiling, clears the could-empty floor.
        int64_t set_hits = 0, clear_hits = 0;
        for (int64_t j = 0; j < n; j++) {
            uint64_t pos_j = rows_out[j] * slice_width + cols_out[j] % slice_width;
            if ((pos_j >> 16) == (pos_i >> 16)) {
                if (types_out[j] == 0) set_hits++; else clear_hits++;
            }
        }
        if (ns[t] + set_hits > caps[t] || ns[t] + set_hits > array_max) return n;
        if (clear_hits > 0 && ns[t] - clear_hits < 1) return n;  // could empty
    }

    // Pass 2 — sequential apply (identical to issuing the calls one by
    // one, including in-batch duplicates and set-then-clear pairs),
    // collecting WAL records for the ops that actually changed state.
    enum { WAL_STACK = 256 };
    uint8_t wal_stack[WAL_STACK * 13];
    uint8_t* wal_buf = wal_stack;
    std::string wal_heap;
    if (n > WAL_STACK) {
        wal_heap.resize((size_t)n * 13);
        wal_buf = reinterpret_cast<uint8_t*>(&wal_heap[0]);
    }
    int64_t n_wal = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t pos = rows_out[i] * slice_width + cols_out[i] % slice_width;
        int64_t t = pn_tab_pos(keys_sorted, n_containers, pos >> 16);
        uint32_t* arr = reinterpret_cast<uint32_t*>(buf_addrs[t]);
        uint32_t low = (uint32_t)(pos & 0xFFFF);
        int64_t cn = ns[t];
        int64_t lo = 0, hi = cn;
        while (lo < hi) {
            int64_t mid = (lo + hi) >> 1;
            if (arr[mid] < low) lo = mid + 1; else hi = mid;
        }
        bool present = (lo < cn && arr[lo] == low);
        if (types_out[i] == 0) {  // SetBit
            if (present) {
                changed_out[i] = 0;
                continue;
            }
            memmove(arr + lo + 1, arr + lo, (size_t)(cn - lo) * sizeof(uint32_t));
            arr[lo] = low;
            ns[t] = cn + 1;
        } else {  // ClearBit
            if (!present) {
                changed_out[i] = 0;
                continue;
            }
            memmove(arr + lo, arr + lo + 1, (size_t)(cn - lo - 1) * sizeof(uint32_t));
            ns[t] = cn - 1;
        }
        changed_out[i] = 1;
        pn_oplog_encode(&types_out[i], &pos, 1, wal_buf + n_wal * 13);
        n_wal++;
    }
    if (wal_fd >= 0 && n_wal) {
        size_t total = (size_t)n_wal * 13, off = 0;
        while (off < total) {
            ssize_t w = write(wal_fd, wal_buf + off, total - off);
            if (w < 0) {
                if (errno == EINTR) continue;
                return -3;  // mutated but not durable: caller raises
            }
            off += (size_t)w;
        }
    }
    *applied = 1;
    return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Serve-lane breadth (the multi-core serving PR): three more request
// shapes answered in ONE GIL-released crossing each, extending
// pn_serve_pairs' single-frame pair lane.
//
//   pn_serve_multi   — pair-count batches spanning SEVERAL armed frames
//                      (each call evaluated against its frame's glut).
//   pn_pql_match_range — matcher for all-Count(Range(...)) bodies; the
//                      Python side rides the existing fused multi-view
//                      evaluator with the parse already done.
//   pn_serve_tree    — arbitrarily nested Count(op-tree over Bitmap)
//                      batches evaluated straight off the fragment's
//                      armed container table: matcher and evaluator are
//                      fused per container block, so intermediate row-id
//                      arrays never materialize.
//
// Every kernel keeps the lane contract: PN_PQL_FALLBACK for ANYTHING
// outside its exact shape, so the Python paths keep all behaviors and
// error messages.
// ---------------------------------------------------------------------------

namespace {

// Sorted-u32 set merges (two-pointer).  Output must not alias inputs for
// or/xor (the write cursor can run ahead of the read cursor); and/andnot
// only shrink, but callers keep output disjoint anyway (ping-pong
// buffers), so no aliasing case exists at all.
static int64_t pn_merge_and(const uint32_t* a, int64_t na,
                            const uint32_t* b, int64_t nb, uint32_t* o) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) i++;
        else if (a[i] > b[j]) j++;
        else { o[k++] = a[i]; i++; j++; }
    }
    return k;
}

static int64_t pn_merge_or(const uint32_t* a, int64_t na,
                           const uint32_t* b, int64_t nb, uint32_t* o) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) o[k++] = a[i++];
        else if (a[i] > b[j]) o[k++] = b[j++];
        else { o[k++] = a[i]; i++; j++; }
    }
    while (i < na) o[k++] = a[i++];
    while (j < nb) o[k++] = b[j++];
    return k;
}

static int64_t pn_merge_xor(const uint32_t* a, int64_t na,
                            const uint32_t* b, int64_t nb, uint32_t* o) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) o[k++] = a[i++];
        else if (a[i] > b[j]) o[k++] = b[j++];
        else { i++; j++; }
    }
    while (i < na) o[k++] = a[i++];
    while (j < nb) o[k++] = b[j++];
    return k;
}

static int64_t pn_merge_andnot(const uint32_t* a, int64_t na,
                               const uint32_t* b, int64_t nb, uint32_t* o) {
    int64_t i = 0, j = 0, k = 0;
    while (i < na && j < nb) {
        if (a[i] < b[j]) o[k++] = a[i++];
        else if (a[i] > b[j]) j++;
        else { i++; j++; }
    }
    while (i < na) o[k++] = a[i++];
    return k;
}

// Nested-tree lane bounds: preorder program size per Count call and op
// nesting depth.  Deeper/larger shapes fall back (the Python tree lane
// has its own depth cap and the sequential path covers the rest).
enum { PN_TREE_MAX_NODES = 128, PN_TREE_MAX_DEPTH = 6 };
// One container's 16-bit value domain bounds every intermediate result.
enum { PN_TREE_BLOCK = 65536 };

struct PnTreeNode {
    int8_t op;       // -1 = Bitmap leaf; else 0=and 1=or 2=xor 3=andnot
    int16_t nchild;  // >= 2 for op nodes
    int64_t row;     // leaf row id
};

// Recursive-descent parse of one op-tree expression into a preorder
// program.  Grammar (frame/row-key labels must match the armed frame):
//   expr := Bitmap(<rowkey>=INT[, frame=F])
//         | Intersect|Union|Xor|Difference '(' expr {',' expr} ')'
// Left-fold evaluation makes n-ary Difference a &~ b &~ c — identical
// to the executor's a &~ (b | c | ...) rewrite.
static bool pn_tree_parse(PairMatcher& p, const char* src, int64_t len,
                          const char* frame, int64_t flen, int allow_default,
                          const char* rowkey, int64_t klen,
                          PnTreeNode* nodes, int64_t* n_nodes, int depth) {
    if (*n_nodes >= PN_TREE_MAX_NODES || depth > PN_TREE_MAX_DEPTH) return false;
    int64_t me = (*n_nodes)++;
    if (!p.ws()) return false;
    int8_t op;
    if (p.lit("Intersect", 9)) op = 0;
    else if (p.lit("Union", 5)) op = 1;
    else if (p.lit("Xor", 3)) op = 2;
    else if (p.lit("Difference", 10)) op = 3;
    else if (p.lit("Bitmap", 6)) op = -1;
    else return false;
    if (op < 0) {
        // Bitmap leaf: (<rowkey>=INT[, frame=...]), args in either order.
        if (!p.ws() || !p.ch('(')) return false;
        int64_t row = -1;
        bool have_frame = false;
        for (int a = 0; a < 2; a++) {
            if (!p.ws()) return false;
            int32_t ks, ke;
            if (!p.ident(&ks, &ke)) return false;
            if (!p.ws() || !p.ch('=')) return false;
            if (!p.ws()) return false;
            if (ke - ks == 5 && memcmp(src + ks, "frame", 5) == 0) {
                if (have_frame) return false;
                int32_t fs, fe;
                char q = src[p.i];
                if (q == '"' || q == '\'') {
                    p.i++;
                    fs = (int32_t)p.i;
                    while (p.i < len && src[p.i] != q) {
                        if (src[p.i] == '\\') return false;
                        p.i++;
                    }
                    if (p.i >= len) return false;
                    fe = (int32_t)p.i;
                    p.i++;
                } else if (!p.ident(&fs, &fe)) {
                    return false;
                }
                if (fe - fs != flen || memcmp(src + fs, frame, (size_t)flen) != 0)
                    return false;
                have_frame = true;
            } else {
                if (row >= 0) return false;
                if (ke - ks != klen || memcmp(src + ks, rowkey, (size_t)klen) != 0)
                    return false;
                if (!p.integer(&row)) return false;
            }
            if (!p.ws()) return false;
            if (src[p.i] == ',') { p.i++; continue; }
            break;
        }
        if (!p.ws() || !p.ch(')')) return false;
        if (row < 0) return false;
        if (!have_frame && !allow_default) return false;
        nodes[me].op = -1;
        nodes[me].nchild = 0;
        nodes[me].row = row;
        return true;
    }
    if (!p.ws() || !p.ch('(')) return false;
    int16_t nchild = 0;
    for (;;) {
        if (!pn_tree_parse(p, src, len, frame, flen, allow_default, rowkey, klen,
                           nodes, n_nodes, depth + 1))
            return false;
        nchild++;
        if (!p.ws()) return false;
        if (src[p.i] == ',') { p.i++; continue; }
        break;
    }
    if (!p.ch(')')) return false;
    if (nchild < 2) return false;
    nodes[me].op = op;
    nodes[me].nchild = nchild;
    nodes[me].row = 0;
    return true;
}

// Per-block tree evaluator over the fragment's armed container table.
// Leaves read container arrays in place (no copy); op nodes fold
// children through per-depth ping-pong buffers.  A leaf whose container
// is a BITMAP (present in bkeys, absent from the array table) sets
// decline — the armed table has no byte view of bitmap containers, so
// the whole request falls back.
struct PnTreeEval {
    const PnTreeNode* nodes;
    const uint64_t* keys;
    const uint64_t* addrs;
    const int64_t* ns;
    int64_t n_containers;
    const uint64_t* bkeys;
    int64_t n_bkeys;
    uint32_t* arena;  // (PN_TREE_MAX_DEPTH + 2) * 2 * PN_TREE_BLOCK
    int64_t cursor;
    uint64_t block;   // container offset within the row span, 0..15
    bool decline;

    const uint32_t* leaf(uint64_t row, int64_t* n_out) {
        uint64_t key = row * 16 + block;
        int64_t t = pn_tab_pos(keys, n_containers, key);
        if (t >= 0) {
            *n_out = ns[t];
            return reinterpret_cast<const uint32_t*>((uintptr_t)addrs[t]);
        }
        if (pn_tab_pos(bkeys, n_bkeys, key) >= 0) decline = true;
        *n_out = 0;  // absent container: empty row segment
        return nullptr;
    }

    const uint32_t* eval(int depth, int64_t* n_out) {
        const PnTreeNode& nd = nodes[cursor++];
        if (nd.op < 0) return leaf((uint64_t)nd.row, n_out);
        uint32_t* bufA = arena + (size_t)depth * 2 * PN_TREE_BLOCK;
        uint32_t* bufB = bufA + PN_TREE_BLOCK;
        bool c0_op = nodes[cursor].op >= 0;
        int64_t na;
        const uint32_t* a = eval(depth + 1, &na);
        if (decline) { *n_out = 0; return nullptr; }
        if (c0_op) {
            // Child 0's result lives in the depth+1 arena, which the next
            // child's evaluation reuses: park it in this depth's spare.
            memcpy(bufB, a, (size_t)na * sizeof(uint32_t));
            a = bufB;
        }
        uint32_t* out_b = bufA;
        for (int k = 1; k < nd.nchild; k++) {
            int64_t nb;
            const uint32_t* b = eval(depth + 1, &nb);
            if (decline) { *n_out = 0; return nullptr; }
            int64_t no;
            switch (nd.op) {
                case 0: no = pn_merge_and(a, na, b, nb, out_b); break;
                case 1: no = pn_merge_or(a, na, b, nb, out_b); break;
                case 2: no = pn_merge_xor(a, na, b, nb, out_b); break;
                default: no = pn_merge_andnot(a, na, b, nb, out_b); break;
            }
            a = out_b;
            na = no;
            out_b = (out_b == bufA) ? bufB : bufA;
        }
        *n_out = na;
        return a;
    }
};

// "YYYY-MM-DDTHH:MM" (pql.TIME_FORMAT) -> Y*1e8 + M*1e6 + D*1e4 + h*1e2 + m.
// Digits-and-separators only; calendar validity stays with the Python
// side (datetime raises there, preserving the sequential error text).
static bool pn_match_time(const char* p, int64_t n, int64_t* out) {
    if (n != 16) return false;
    for (int k = 0; k < 16; k++) {
        char c = p[k];
        if (k == 4 || k == 7) { if (c != '-') return false; }
        else if (k == 10) { if (c != 'T') return false; }
        else if (k == 13) { if (c != ':') return false; }
        else if (c < '0' || c > '9') return false;
    }
    int64_t Y = (p[0]-'0')*1000 + (p[1]-'0')*100 + (p[2]-'0')*10 + (p[3]-'0');
    int64_t M = (p[5]-'0')*10 + (p[6]-'0');
    int64_t D = (p[8]-'0')*10 + (p[9]-'0');
    int64_t h = (p[11]-'0')*10 + (p[12]-'0');
    int64_t m = (p[14]-'0')*10 + (p[15]-'0');
    *out = Y*100000000LL + M*1000000LL + D*10000LL + h*100LL + m;
    return true;
}

}  // namespace

extern "C" {

// Multi-frame serving lane: pn_serve_pairs generalized to K armed frame
// states.  names/rlabels are concatenated frame-name and row-label bytes
// with K+1 offset fences; rs/ps/gram_addrs are RAW base addresses of
// each state's glut arrays (sorted row ids, positions, Gram), n_rows and
// gram_dims their extents.  default_sid maps an absent frame= arg (< 0
// = no armed default frame -> fallback).  Returns the call count with
// counts in out[], or PN_PQL_FALLBACK (unknown frame, label mismatch,
// unknown row, parse mismatch).
int64_t pn_serve_multi(const char* src, int64_t len,
                       const char* names, const int64_t* name_offs,
                       const char* rlabels, const int64_t* rlabel_offs,
                       int64_t n_states, int64_t default_sid,
                       const uint64_t* rs_addrs, const uint64_t* ps_addrs,
                       const uint64_t* gram_addrs, const int64_t* n_rows,
                       const int64_t* gram_dims,
                       int64_t* out, int64_t cap) {
    enum { MAXC = 4096, TAB = 16 };
    static thread_local uint8_t op_ids[MAXC];
    static thread_local int32_t frame_ids[MAXC], key_ids[MAXC];
    static thread_local int64_t r1[MAXC], r2[MAXC];
    int32_t uf_s[TAB], uf_e[TAB], uk_s[TAB], uk_e[TAB];
    int32_t n_frames = 0, n_keys = 0;
    int64_t n = pn_pql_match_pairs(src, len, op_ids, frame_ids, key_ids, r1, r2,
                                   cap < MAXC ? cap : MAXC,
                                   uf_s, uf_e, &n_frames, uk_s, uk_e, &n_keys,
                                   TAB);
    if (n < 0) return PN_PQL_FALLBACK;
    // Resolve each interned frame span to an armed state by content.
    int32_t f_sid[TAB];
    for (int32_t t = 0; t < n_frames; t++) {
        f_sid[t] = -1;
        int32_t l = uf_e[t] - uf_s[t];
        for (int64_t sid = 0; sid < n_states; sid++) {
            int64_t nl = name_offs[sid + 1] - name_offs[sid];
            if (nl == l &&
                memcmp(src + uf_s[t], names + name_offs[sid], (size_t)l) == 0) {
                f_sid[t] = (int32_t)sid;
                break;
            }
        }
        if (f_sid[t] < 0) return PN_PQL_FALLBACK;
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t sid = frame_ids[i] < 0 ? default_sid : f_sid[frame_ids[i]];
        if (sid < 0) return PN_PQL_FALLBACK;
        // The call's row-key label must be ITS frame's row label.
        int32_t kt = key_ids[i];
        int64_t kl = rlabel_offs[sid + 1] - rlabel_offs[sid];
        if (uk_e[kt] - uk_s[kt] != kl ||
            memcmp(src + uk_s[kt], rlabels + rlabel_offs[sid], (size_t)kl) != 0)
            return PN_PQL_FALLBACK;
        const int64_t* rs = reinterpret_cast<const int64_t*>((uintptr_t)rs_addrs[sid]);
        const int32_t* ps = reinterpret_cast<const int32_t*>((uintptr_t)ps_addrs[sid]);
        const int64_t* gram = reinterpret_cast<const int64_t*>((uintptr_t)gram_addrs[sid]);
        int64_t nr = n_rows[sid], gd = gram_dims[sid];
        int64_t i1 = pn_row_pos(rs, nr, r1[i]);
        int64_t i2 = pn_row_pos(rs, nr, r2[i]);
        if (i1 < 0 || i2 < 0) return PN_PQL_FALLBACK;
        int64_t p1 = ps[i1], p2 = ps[i2];
        int64_t g = gram[p1 * gd + p2];
        switch (op_ids[i]) {
            case 0: out[i] = g; break;
            case 1: out[i] = gram[p1 * gd + p1] + gram[p2 * gd + p2] - g; break;
            case 2: out[i] = gram[p1 * gd + p1] + gram[p2 * gd + p2] - 2 * g; break;
            case 3: out[i] = gram[p1 * gd + p1] - g; break;
            default: return PN_PQL_FALLBACK;
        }
    }
    return n;
}

// Matcher for an all-Count(Range(...)) request: per call the frame id
// (interned; -1 = default), row-key label id (interned), row id, and the
// start/end timestamps packed as digit integers (see pn_match_time).
// Args accepted in any order; each exactly once; start/end must be
// quoted.  Returns the call count or PN_PQL_FALLBACK; like the pair
// matcher, a single-call body falls back (fusing buys nothing there).
int64_t pn_pql_match_range(const char* src, int64_t len,
                           int32_t* frame_ids, int32_t* key_ids, int64_t* rows,
                           int64_t* starts, int64_t* ends, int64_t call_cap,
                           int32_t* uf_s, int32_t* uf_e, int32_t* n_frames,
                           int32_t* uk_s, int32_t* uk_e, int32_t* n_keys,
                           int32_t tab_cap) {
    PairMatcher p = {src, len, 0};
    int64_t n = 0;
    *n_frames = 0;
    *n_keys = 0;
    while (p.ws()) {
        if (n >= call_cap) return PN_PQL_FALLBACK;
        if (!p.lit("Count", 5)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('(')) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.lit("Range", 5)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('(')) return PN_PQL_FALLBACK;
        int32_t f_s = -1, f_e = -1, k_s = -1, k_e = -1;
        int64_t rv = -1, start = -1, end = -1;
        for (int a = 0; a < 4; a++) {
            if (!p.ws()) return PN_PQL_FALLBACK;
            int32_t ks, ke;
            if (!p.ident(&ks, &ke)) return PN_PQL_FALLBACK;
            if (!p.ws() || !p.ch('=')) return PN_PQL_FALLBACK;
            if (!p.ws()) return PN_PQL_FALLBACK;
            bool is_frame = (ke - ks == 5 && memcmp(src + ks, "frame", 5) == 0);
            bool is_start = (ke - ks == 5 && memcmp(src + ks, "start", 5) == 0);
            bool is_end = (ke - ks == 3 && memcmp(src + ks, "end", 3) == 0);
            if (is_frame) {
                if (f_s >= 0) return PN_PQL_FALLBACK;
                char q = src[p.i];
                if (q == '"' || q == '\'') {
                    p.i++;
                    f_s = (int32_t)p.i;
                    while (p.i < len && src[p.i] != q) {
                        if (src[p.i] == '\\') return PN_PQL_FALLBACK;
                        p.i++;
                    }
                    if (p.i >= len) return PN_PQL_FALLBACK;
                    f_e = (int32_t)p.i;
                    p.i++;
                } else if (!p.ident(&f_s, &f_e)) {
                    return PN_PQL_FALLBACK;
                }
            } else if (is_start || is_end) {
                if ((is_start ? start : end) >= 0) return PN_PQL_FALLBACK;
                char q = src[p.i];
                if (q != '"' && q != '\'') return PN_PQL_FALLBACK;
                p.i++;
                int64_t vs = p.i;
                while (p.i < len && src[p.i] != q) {
                    if (src[p.i] == '\\') return PN_PQL_FALLBACK;
                    p.i++;
                }
                if (p.i >= len) return PN_PQL_FALLBACK;
                int64_t packed;
                if (!pn_match_time(src + vs, p.i - vs, &packed))
                    return PN_PQL_FALLBACK;
                p.i++;
                if (is_start) start = packed; else end = packed;
            } else {
                if (rv >= 0) return PN_PQL_FALLBACK;
                if (!p.integer(&rv)) return PN_PQL_FALLBACK;
                k_s = ks;
                k_e = ke;
            }
            if (!p.ws()) return PN_PQL_FALLBACK;
            if (src[p.i] == ',') {
                p.i++;
                continue;
            }
            break;
        }
        if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;  // close Range
        if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;  // close Count
        if (rv < 0 || start < 0 || end < 0) return PN_PQL_FALLBACK;
        int32_t fid = (f_s < 0)
                          ? -1
                          : intern_span(src, f_s, f_e, uf_s, uf_e, n_frames, tab_cap);
        int32_t kid = intern_span(src, k_s, k_e, uk_s, uk_e, n_keys, tab_cap);
        if (fid == -2 || kid == -2) return PN_PQL_FALLBACK;
        frame_ids[n] = fid;
        key_ids[n] = kid;
        rows[n] = rv;
        starts[n] = start;
        ends[n] = end;
        n++;
    }
    return n >= 2 ? n : PN_PQL_FALLBACK;
}

// Fused nested-tree serving lane: parse an all-Count(op-tree) body and
// evaluate every call straight off the fragment's armed container table
// (single-slice frames; the caller holds the fragment lock so the
// buffers cannot move mid-read).  keys/addrs/ns describe the ARRAY
// containers (pn_write_batch's table); bkeys is the sorted key set of
// BITMAP containers — a leaf touching one declines (the table carries no
// byte view of bitmaps).  Absent keys are empty row segments.  Returns
// the call count with counts in out[], or PN_PQL_FALLBACK.
int64_t pn_serve_tree(const char* src, int64_t len,
                      const char* frame, int64_t flen, int64_t allow_default,
                      const char* rowkey, int64_t klen,
                      const uint64_t* keys_sorted, const uint64_t* buf_addrs,
                      const int64_t* ns, int64_t n_containers,
                      const uint64_t* bkeys, int64_t n_bkeys,
                      int64_t* out, int64_t cap) {
    PairMatcher p = {src, len, 0};
    static thread_local std::vector<uint32_t> arena;
    int64_t n = 0;
    while (p.ws()) {
        if (n >= cap) return PN_PQL_FALLBACK;
        if (!p.lit("Count", 5)) return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch('(')) return PN_PQL_FALLBACK;
        PnTreeNode nodes[PN_TREE_MAX_NODES];
        int64_t n_nodes = 0;
        if (!pn_tree_parse(p, src, len, frame, flen, (int)allow_default,
                           rowkey, klen, nodes, &n_nodes, 0))
            return PN_PQL_FALLBACK;
        if (!p.ws() || !p.ch(')')) return PN_PQL_FALLBACK;  // close Count
        // integer() bounds rows below 1e18, so row*16+15 fits uint64.
        if (nodes[0].op < 0) {
            // Plain Count(Bitmap): the row's cardinality straight off
            // the table — no merges, no scratch.
            uint64_t row = (uint64_t)nodes[0].row;
            int64_t total = 0;
            for (uint64_t j = 0; j < 16; j++) {
                uint64_t key = row * 16 + j;
                int64_t t = pn_tab_pos(keys_sorted, n_containers, key);
                if (t >= 0) total += ns[t];
                else if (pn_tab_pos(bkeys, n_bkeys, key) >= 0)
                    return PN_PQL_FALLBACK;
            }
            out[n++] = total;
            continue;
        }
        if (arena.empty())
            arena.resize((size_t)(PN_TREE_MAX_DEPTH + 2) * 2 * PN_TREE_BLOCK);
        int64_t total = 0;
        for (uint64_t j = 0; j < 16; j++) {
            PnTreeEval ev = {nodes, keys_sorted, buf_addrs, ns, n_containers,
                             bkeys, n_bkeys, arena.data(), 0, j, false};
            int64_t rn;
            ev.eval(0, &rn);
            if (ev.decline) return PN_PQL_FALLBACK;
            total += rn;
        }
        out[n++] = total;
    }
    return n >= 1 ? n : PN_PQL_FALLBACK;
}

}  // extern "C"

extern "C" {

// Returns the number of calls parsed (preorder), or PN_PQL_FALLBACK when
// the source needs the full Python parser.  n_args_out gets the total
// arg-slot count on success.
int64_t pn_pql_parse(const char* src, int64_t len,
                     int32_t* cname_s, int32_t* cname_e, int32_t* cnchild,
                     int32_t* cnargs, int32_t* cargs_off, int64_t call_cap,
                     int32_t* ak_s, int32_t* ak_e, int32_t* atype,
                     int64_t* aint, int32_t* av_s, int32_t* av_e,
                     int64_t arg_cap, int64_t* n_args_out) {
    PqlOut out = {cname_s, cname_e, cnchild, cnargs, cargs_off, call_cap,
                  ak_s, ak_e, atype, aint, av_s, av_e, arg_cap};
    PqlParser p = {src, len, 0, &out, 0, 0, 0};
    while (p.ws()) {
        if (!p.call()) return PN_PQL_FALLBACK;
    }
    *n_args_out = p.n_args;
    return p.n_calls;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Incremental snapshot encoder (fragment.go:1017-1057 snapshot analog)
//
// A fragment snapshot rewrites the whole cookie-12346 image every MaxOpN
// ops; rebuilding it container-by-container in Python costs ~4us per
// container, which dominates the SetBit hot path on sparse fragments
// (tens of thousands of tiny containers).  This keeps a C++-side mirror
// of the encoded per-container payloads: Python pushes only the DIRTY
// containers after each batch of mutations, and emit() streams the full
// image (header + offsets + payloads) from C state in one call.
// ---------------------------------------------------------------------------

#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {
struct SnapEntry {
    uint32_t n;
    std::string payload;
};
struct SnapState {
    std::map<uint64_t, SnapEntry> containers;  // sorted by key
    size_t payload_bytes = 0;
};
std::mutex g_snap_mu;
std::unordered_map<int64_t, SnapState*>& snap_registry() {
    static auto* r = new std::unordered_map<int64_t, SnapState*>();
    return *r;
}
int64_t g_snap_next = 1;

SnapState* snap_get(int64_t h) {
    auto& r = snap_registry();
    auto it = r.find(h);
    return it == r.end() ? nullptr : it->second;
}
}  // namespace

extern "C" {

int64_t pn_snap_new() {
    std::lock_guard<std::mutex> g(g_snap_mu);
    int64_t h = g_snap_next++;
    snap_registry()[h] = new SnapState();
    return h;
}

void pn_snap_free(int64_t h) {
    std::lock_guard<std::mutex> g(g_snap_mu);
    auto& r = snap_registry();
    auto it = r.find(h);
    if (it != r.end()) {
        delete it->second;
        r.erase(it);
    }
}

// Upsert one container's encoded payload (n values; len payload bytes).
void pn_snap_set(int64_t h, uint64_t key, uint32_t n, const uint8_t* payload,
                 size_t len) {
    std::lock_guard<std::mutex> g(g_snap_mu);
    SnapState* s = snap_get(h);
    if (!s) return;
    auto it = s->containers.find(key);
    if (it != s->containers.end()) {
        s->payload_bytes -= it->second.payload.size();
        it->second.n = n;
        it->second.payload.assign(reinterpret_cast<const char*>(payload), len);
        s->payload_bytes += len;
    } else {
        auto& e = s->containers[key];
        e.n = n;
        e.payload.assign(reinterpret_cast<const char*>(payload), len);
        s->payload_bytes += len;
    }
}

void pn_snap_del(int64_t h, uint64_t key) {
    std::lock_guard<std::mutex> g(g_snap_mu);
    SnapState* s = snap_get(h);
    if (!s) return;
    auto it = s->containers.find(key);
    if (it != s->containers.end()) {
        s->payload_bytes -= it->second.payload.size();
        s->containers.erase(it);
    }
}

int64_t pn_snap_image_size(int64_t h) {
    std::lock_guard<std::mutex> g(g_snap_mu);
    SnapState* s = snap_get(h);
    if (!s) return -1;
    size_t n = s->containers.size();
    return (int64_t)(8 + n * 16 + s->payload_bytes);
}

// Emit the full cookie-12346 image; returns bytes written or -1 if cap is
// too small / the handle is unknown.
int64_t pn_snap_emit(int64_t h, uint8_t* out, size_t cap) {
    std::lock_guard<std::mutex> g(g_snap_mu);
    SnapState* s = snap_get(h);
    if (!s) return -1;
    size_t n = s->containers.size();
    size_t total = 8 + n * 16 + s->payload_bytes;
    if (cap < total) return -1;
    uint32_t cookie = 12346;
    std::memcpy(out, &cookie, 4);
    uint32_t n32 = (uint32_t)n;
    std::memcpy(out + 4, &n32, 4);
    uint8_t* hdr = out + 8;
    uint8_t* offs = out + 8 + n * 12;
    uint8_t* pay = out + 8 + n * 16;
    uint32_t off = (uint32_t)(8 + n * 16);
    for (auto& kv : s->containers) {
        uint64_t key = kv.first;
        uint32_t n1 = kv.second.n - 1;
        std::memcpy(hdr, &key, 8);
        std::memcpy(hdr + 8, &n1, 4);
        hdr += 12;
        std::memcpy(offs, &off, 4);
        offs += 4;
        size_t len = kv.second.payload.size();
        std::memcpy(pay, kv.second.payload.data(), len);
        pay += len;
        off += (uint32_t)len;
    }
    return (int64_t)total;
}

}  // extern "C"
