// Native host-runtime kernels for pilosa_tpu.
//
// The reference accelerates its host hot loops with AMD64 assembly
// (roaring/assembly_amd64.s); the TPU build's device hot path is
// XLA/Pallas, and THIS library covers the host-side runtime loops that
// stay on CPU: protobuf varint packing for the data plane, WAL op-record
// encode/decode with FNV-1a checksums, CSV ingest parsing, and popcount
// fallbacks.  Loaded from Python via ctypes (pilosa_tpu/native.py) with a
// pure-Python fallback when the toolchain is unavailable.
//
// Build: make -C native   (produces libpilosa_native.so)

#include <cstddef>
#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Hashes
// ---------------------------------------------------------------------------

uint64_t pn_fnv1a64(const uint8_t* data, size_t len) {
    uint64_t h = 0xCBF29CE484222325ULL;
    for (size_t i = 0; i < len; i++) {
        h ^= data[i];
        h *= 0x100000001B3ULL;
    }
    return h;
}

uint32_t pn_fnv1a32(const uint8_t* data, size_t len) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < len; i++) {
        h ^= data[i];
        h *= 16777619u;
    }
    return h;
}

// ---------------------------------------------------------------------------
// Popcount (host fallback; device path is lax.population_count)
// ---------------------------------------------------------------------------

uint64_t pn_popcount_u32(const uint32_t* words, size_t n) {
    uint64_t total = 0;
    for (size_t i = 0; i < n; i++) total += (uint64_t)__builtin_popcount(words[i]);
    return total;
}

uint64_t pn_popcount_and_u32(const uint32_t* a, const uint32_t* b, size_t n) {
    uint64_t total = 0;
    for (size_t i = 0; i < n; i++) total += (uint64_t)__builtin_popcount(a[i] & b[i]);
    return total;
}

// ---------------------------------------------------------------------------
// Protobuf varint packing (wire.py data plane: packed repeated uint64)
// ---------------------------------------------------------------------------

// Encode n uint64 values as concatenated varints. Returns bytes written,
// or -1 if cap is too small. Worst case 10 bytes/value.
int64_t pn_varint_encode(const uint64_t* vals, size_t n, uint8_t* out, size_t cap) {
    size_t o = 0;
    for (size_t i = 0; i < n; i++) {
        uint64_t v = vals[i];
        do {
            if (o >= cap) return -1;
            uint8_t b = v & 0x7F;
            v >>= 7;
            out[o++] = v ? (b | 0x80) : b;
        } while (v);
    }
    return (int64_t)o;
}

// Decode concatenated varints. Returns count decoded, or -1 on truncation,
// uint64 overflow (overlong varint), or output-buffer overflow.
int64_t pn_varint_decode(const uint8_t* buf, size_t len, uint64_t* out, size_t cap) {
    size_t i = 0, n = 0;
    while (i < len) {
        uint64_t v = 0;
        int shift = 0;
        for (;;) {
            if (i >= len || shift > 63) return -1;
            uint8_t b = buf[i++];
            // Byte 10 (shift 63) may only carry the final value bit; a set
            // continuation or any higher value bit overflows uint64.
            if (shift == 63 && (b & 0xFE)) return -1;
            v |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
        }
        if (n >= cap) return -1;
        out[n++] = v;
    }
    return (int64_t)n;
}

// ---------------------------------------------------------------------------
// WAL op records: [typ u8 | value u64le | fnv1a32(first 9 bytes) u32le]
// (roaring.go:1560-1626 format)
// ---------------------------------------------------------------------------

void pn_oplog_encode(const uint8_t* types, const uint64_t* vals, size_t n, uint8_t* out) {
    for (size_t i = 0; i < n; i++) {
        uint8_t* rec = out + i * 13;
        rec[0] = types[i];
        uint64_t v = vals[i];
        for (int j = 0; j < 8; j++) rec[1 + j] = (v >> (8 * j)) & 0xFF;
        uint32_t chk = pn_fnv1a32(rec, 9);
        for (int j = 0; j < 4; j++) rec[9 + j] = (chk >> (8 * j)) & 0xFF;
    }
}

// Returns ops decoded, or -(index+1) of the first corrupt record.
int64_t pn_oplog_decode(const uint8_t* buf, size_t len, uint8_t* types, uint64_t* vals) {
    size_t n = len / 13;
    for (size_t i = 0; i < n; i++) {
        const uint8_t* rec = buf + i * 13;
        uint32_t want = 0;
        for (int j = 0; j < 4; j++) want |= (uint32_t)rec[9 + j] << (8 * j);
        if (pn_fnv1a32(rec, 9) != want) return -(int64_t)(i + 1);
        uint8_t t = rec[0];
        if (t > 1) return -(int64_t)(i + 1);
        types[i] = t;
        uint64_t v = 0;
        for (int j = 0; j < 8; j++) v |= (uint64_t)rec[1 + j] << (8 * j);
        vals[i] = v;
    }
    return (int64_t)n;
}

// ---------------------------------------------------------------------------
// CSV ingest: parse "row,col[,timestamp]\n" lines into arrays
// (ctl/import.go hot loop)
// ---------------------------------------------------------------------------

// Returns rows parsed, or -(lineno) of the first malformed line.
int64_t pn_parse_csv(const char* buf, size_t len, uint64_t* rows, uint64_t* cols,
                     int64_t* ts, size_t cap) {
    size_t i = 0, n = 0;
    int64_t line = 1;
    while (i < len) {
        // skip blank lines
        if (buf[i] == '\n' || buf[i] == '\r') {
            if (buf[i] == '\n') line++;
            i++;
            continue;
        }
        if (n >= cap) return -line;
        uint64_t vals[3] = {0, 0, 0};
        int field = 0;
        // Per-field state so "5," / ",7" / "1 2" are rejected exactly like
        // the Python fallback (int() allows surrounding, not interior,
        // whitespace; empty row/col fields are malformed).
        bool has_digit[3] = {false, false, false};
        bool digits_done[3] = {false, false, false};  // saw space after digits
        bool line_content = false;                    // any digit or comma
        for (; i < len && buf[i] != '\n'; i++) {
            char c = buf[i];
            if (c >= '0' && c <= '9') {
                if (digits_done[field]) return -line;  // "1 2" in one field
                uint64_t d = (uint64_t)(c - '0');
                // uint64 overflow check: the fallback rejects ids >= 2^64
                // rather than wrapping them onto the wrong bit.
                if (vals[field] > (0xFFFFFFFFFFFFFFFFULL - d) / 10) return -line;
                vals[field] = vals[field] * 10 + d;
                has_digit[field] = true;
                line_content = true;
            } else if (c == ',') {
                if (field >= 2) return -line;
                field++;
                line_content = true;
            } else if (c == '\r' || c == ' ') {
                if (has_digit[field]) digits_done[field] = true;
            } else {
                return -line;
            }
        }
        if (i < len) i++;  // consume newline
        if (!line_content) {  // whitespace-only line: skipped, like strip()
            line++;
            continue;
        }
        // Row and column must each carry digits; an empty (or blank)
        // timestamp field means 0 — the fallback strips the line and
        // int() strips field-surrounding spaces, so blanks are legal there.
        if (field < 1 || !has_digit[0] || !has_digit[1]) return -line;
        if (field == 2 && vals[2] > 0x7FFFFFFFFFFFFFFFULL) return -line;  // ts is int64
        rows[n] = vals[0];
        cols[n] = vals[1];
        ts[n] = (field == 2) ? (int64_t)vals[2] : 0;
        n++;
        line++;
    }
    return (int64_t)n;
}

}  // extern "C"
