/* Measured CPU stand-in for the reference's IntersectionCount hot loop.
 *
 * The Go toolchain is absent in this environment (BASELINE.md), so the
 * reference's own `go test -bench` cannot run.  This program measures
 * the SAME inner loop its assembly implements — popcntAndSliceAsm
 * (Σ popcount(a[i] & b[i]) over []uint64, one POPCNTQ per 8 bytes,
 * reference roaring/assembly_amd64.s:60-77) — compiled with -mpopcnt
 * so the compiler emits the same POPCNT instruction the asm uses.  The
 * result is a measured upper bound for what the reference's kernel
 * layer sustains per core on THIS host, replacing the literature
 * estimate in the vs_baseline accounting.
 *
 * Build/run: gcc -O2 -mpopcnt -o refloop_bench refloop_bench.c && ./refloop_bench
 * Prints one JSON line: bytes/s through the AND+POPCNT loop and the
 * equivalent batch-256 pair-count q/s at the headline shape.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

static double now_s(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec + ts.tv_nsec * 1e-9;
}

int main(void) {
    /* One slice-row pair: 2^20 bits = 16384 uint64 words = 128 KiB per
     * operand (the reference's fragment row width, fragment.go:47).
     * Round-4 note: an earlier revision used 131072 words (8x the real
     * row width), which deflated the derived reference pair rates 8x;
     * the bytes/s figure was always self-consistent.  Fixed here so the
     * printed pair_qps fields are the honest per-core reference bound. */
    const size_t words = 16384;
    /* 512 rows x 128 KiB = 64 MiB working set: larger than L3 so the
     * loop is DRAM-bound like the reference's at-scale regime (the same
     * working-set size the pre-fix revision measured). */
    const int rows = 512;
    uint64_t *data = malloc(rows * words * 8);
    uint64_t seed = 0x9E3779B97F4A7C15ull;
    for (size_t i = 0; i < rows * words; i++) {
        seed ^= seed << 13; seed ^= seed >> 7; seed ^= seed << 17;
        data[i] = seed;
    }
    /* Best of 5 runs of a fixed-size stream (64 iters x 256 pairs x
     * 2 x 128 KiB = 4 GiB of operand reads per run). */
    const int pairs_per_iter = 256;
    int iters = 64;
    uint64_t sink = 0;
    double best = 1e30;
    for (int run = 0; run < 5; run++) {
        double t0 = now_s();
        for (int it = 0; it < iters; it++) {
            for (int p = 0; p < pairs_per_iter; p++) {
                /* Both operands cycle with the iteration so each run
                 * touches the full row working set from both streams
                 * and a != b always (a==b would halve real traffic). */
                int ia = (p * 2 + it) % rows;
                int ib = (p * 2 + 3 * it + 1) % rows;
                if (ib == ia) ib = (ib + 1) % rows;
                const uint64_t *a = data + ia * words;
                const uint64_t *b = data + ib * words;
                uint64_t acc = 0;
                for (size_t i = 0; i < words; i++)
                    acc += (uint64_t)__builtin_popcountll(a[i] & b[i]);
                sink += acc;
            }
        }
        double dt = now_s() - t0;
        if (dt < best) best = dt;
    }
    double bytes = (double)iters * pairs_per_iter * 2.0 * words * 8.0;
    double qps = (double)iters * pairs_per_iter / best;
    printf("{\"metric\": \"ref_and_popcnt_loop\", \"bytes_per_s\": %.3e, "
           "\"pair_qps_1slice\": %.1f, \"pair_qps_16slices\": %.1f, "
           "\"sink\": %llu}\n",
           bytes / best, qps, qps / 16.0, (unsigned long long)(sink & 1));
    free(data);
    return 0;
}
