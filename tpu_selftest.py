"""One-command REAL-TPU kernel validation: every Pallas kernel and
strategy tier vs numpy ground truth on the actual chip.

The pytest suite pins JAX_PLATFORMS=cpu (kernels run in interpret mode
there), so this script is the fast way to prove the real Mosaic lowering
of every kernel after a change: ``python tpu_selftest.py`` (~1 min warm,
a few minutes with cold compiles).  Exits non-zero on any mismatch.

Covers: fused_count1/count2 (incl. shared-b and tiled), resident /
gather / row-major pipelined pair kernels, multi-fold (slice-major and
row-major), fused_topn_counts, the chunked Gram (scan path) vs the
one-shot, and dispatch-level 3D/4D parity.
"""

from __future__ import annotations

import sys

import numpy as np


def main() -> int:
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        print(f"ERROR: backend is {jax.default_backend()}, not tpu", file=sys.stderr)
        return 2

    from pilosa_tpu.ops import bitwise as bw
    from pilosa_tpu.ops import dispatch
    from pilosa_tpu.ops.pallas_kernels import (
        fused_count1,
        fused_count2,
        fused_gather_count2,
        fused_gather_count2_rowmajor,
        fused_gather_count_multi,
        fused_gather_count_multi_rowmajor,
        fused_resident_count2,
        fused_topn_counts,
    )

    rng = np.random.default_rng(2026)
    S, R, W, B, K = 4, 96, 32768, 64, 4
    rm = rng.integers(0, 1 << 32, size=(S, R, W), dtype=np.uint32)
    rm4 = jax.device_put(rm.reshape(S, R, W // 128, 128))
    rm_t4 = jax.device_put(
        np.ascontiguousarray(rm.transpose(1, 0, 2)).reshape(R, S, W // 128, 128)
    )
    pairs = rng.integers(0, R, size=(B, 2), dtype=np.int32)
    idx = rng.integers(0, R, size=(B, K), dtype=np.int32)
    src = rng.integers(0, 1 << 32, size=(S, W), dtype=np.uint32)
    ok = True

    def chk(name, got, want):
        nonlocal ok
        if not np.array_equal(np.asarray(got), want):
            ok = False
            print(f"FAIL {name}")
        else:
            print(f"ok   {name}")

    a2, b2 = rm[0], rm[1]  # [R, W] stacks
    chk("fused_count1", fused_count1(jnp.asarray(a2)), bw.np_popcount(a2).sum(axis=1))
    for op in ("and", "or", "xor", "andnot"):
        r = {"and": a2 & b2, "or": a2 | b2, "xor": a2 ^ b2, "andnot": a2 & ~b2}[op]
        chk(f"fused_count2 {op}", fused_count2(op, jnp.asarray(a2), jnp.asarray(b2)),
            bw.np_popcount(r).sum(axis=1))
    chk("fused_count2 shared-b",
        fused_count2("and", jnp.asarray(a2), jnp.asarray(b2[0])),
        bw.np_popcount(a2 & b2[0]).sum(axis=1))

    def pair_want(op):
        a = rm[:, pairs[:, 0], :]
        b = rm[:, pairs[:, 1], :]
        r = {"and": a & b, "or": a | b, "xor": a ^ b, "andnot": a & ~b}[op]
        return bw.np_popcount(r).reshape(S, B, -1).sum(axis=(0, 2))

    dp = jnp.asarray(pairs)
    for op in ("and", "or", "xor", "andnot"):
        chk(f"resident {op}", fused_resident_count2(op, rm4, dp), pair_want(op))
        chk(f"gather {op}", fused_gather_count2(op, rm4, dp), pair_want(op))
        chk(f"rowmajor {op}", fused_gather_count2_rowmajor(op, rm_t4, dp), pair_want(op))

    di = jnp.asarray(idx)
    for op in ("and", "or", "andnot"):
        want = bw.np_gather_count_multi(op, rm, idx)
        chk(f"multi {op}", fused_gather_count_multi(op, rm4, di), want)
        chk(f"multi rowmajor {op}", fused_gather_count_multi_rowmajor(op, rm_t4, di), want)

    chk("topn_counts",
        fused_topn_counts(rm4, jnp.asarray(src.reshape(S, W // 128, 128))),
        bw.np_popcount(rm & src[:, None, :]).reshape(S, R, -1).sum(axis=(0, 2)))

    # Round-5 kernels: perfect-tree fold + all-slice TopN candidate scorer.
    from pilosa_tpu.ops.pallas_kernels import (
        fused_gather_count_tree,
        fused_gather_src_counts,
    )

    for D in (2, 3, 4):
        Kt = 1 << D
        leaves = rng.integers(0, R, size=(B, Kt), dtype=np.int32)
        opc = rng.integers(0, 5, size=(B, Kt - 1), dtype=np.int32)
        # Chunked reference: one-shot np gather at D=4 materializes
        # ~2 GB (+ popcount temporaries) — chunk the batch instead.
        want_t = np.concatenate([
            bw.np_gather_count_tree(rm, leaves[i : i + 8], opc[i : i + 8])
            for i in range(0, B, 8)
        ])
        chk(f"tree D={D}",
            fused_gather_count_tree(rm4, jnp.asarray(leaves), jnp.asarray(opc)),
            want_t)
    cand = rng.integers(0, R, size=(17,), dtype=np.int32)
    chk("gather_src_counts",
        fused_gather_src_counts(
            rm4, jnp.asarray(cand), jnp.asarray(src.reshape(S, W // 128, 128))
        ),
        np.stack([
            np.array([int(bw.np_popcount(rm[s, p] & src[s]).sum()) for p in cand])
            for s in range(S)
        ]))

    g1 = np.asarray(bw.pair_gram(jnp.asarray(rm)))
    orig = bw.GRAM_ONESHOT_BYTES
    bw.GRAM_ONESHOT_BYTES = 1
    try:
        g2 = np.asarray(bw.pair_gram(rm4))
    finally:
        bw.GRAM_ONESHOT_BYTES = orig
    chk("chunked gram == one-shot", g2, g1)

    for op in ("and", "or"):
        chk(f"dispatch 3D/4D parity {op}",
            dispatch.gather_count(op, rm4, dp, allow_gram=False),
            np.asarray(dispatch.gather_count(op, jnp.asarray(rm), dp, allow_gram=False)))

    # Generated differential fuzz: the SAME lane-by-lane random cases the
    # CI suite runs in interpret mode (tests/test_differential_kernels.py),
    # here against the real Mosaic lowering.  Case count via
    # PILOSA_TPU_SELFTEST_CASES (shape buckets bound recompiles).
    import os

    from pilosa_tpu.ops import diffcheck

    n_cases = int(os.environ.get("PILOSA_TPU_SELFTEST_CASES", "8"))
    failures = diffcheck.run_lanes(seed=2026, cases_per_lane=n_cases, interpret=False)
    for f in failures:
        ok = False
        print(f"FAIL fuzz {f}", file=sys.stderr)
    if not failures:
        print(f"OK   fuzz: {n_cases} generated cases/lane, all lanes match numpy")

    print("ALL OK" if ok else "FAILURES", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
